"""Synthetic LendingClub-schema data generator.

The reference's raw data lives behind DVC pointers to a private S3 bucket
(`data/1-raw/lending-club-2007-2020Q3/*.dvc`) and cannot be fetched offline.
This module generates a raw frame with the same observable schema the pipeline
consumes — including the string quirks the cleaning stage must handle
(`" 36 months"`, `"13.56%"`, `"Apr-2005"`, `"10+ years"`, `"< 1 year"`),
`Unnamed: 0` index artifacts, >70%-null junk columns, duplicate rows, and a
`loan_status` column covering every key of the label map
(`feature_engineering.py:85-94`).

The default label is planted as a Bernoulli draw from a nonlinear
risk score over fico / dti / int_rate / grade / term / utilization with
interactions, so tree models meaningfully beat linear ones and tuned models can
reach the reference's headline AUC regime (~0.95, BASELINE.md).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from cobalt_smart_lender_ai_tpu.data import schema

_MONTHS = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
           "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")


def _sigmoid(z: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-z))


def _lognormal(rng, mean: float, sigma: float, n: int) -> np.ndarray:
    return rng.lognormal(mean, sigma, n)


def synthetic_lendingclub_frame(
    n_rows: int = 10_000,
    seed: int = 0,
    *,
    missing_junk_cols: int = 3,
    duplicate_fraction: float = 0.002,
    signal_scale: float = 3.5,
) -> pd.DataFrame:
    """Build a raw-schema frame of ``n_rows`` loans (plus a few duplicates)."""
    rng = np.random.default_rng(seed)
    n = n_rows

    # --- Core credit variables with realistic correlation structure ----------
    fico_low = np.clip(rng.normal(695, 32, n), 630, 845).round(0)
    fico_high = fico_low + 4.0
    # last_fico drifts from origination fico; big drops signal distress.
    fico_drift = rng.normal(0, 45, n) - 20 * (rng.random(n) < 0.15)
    last_fico_high = np.clip(fico_high + fico_drift, 300, 850).round(0)

    grade_q = np.clip(
        (850 - fico_low) / 40 + rng.normal(0, 1.0, n), 0, 6.999
    )
    grade_idx = grade_q.astype(int)  # 0..6 → A..G
    sub = rng.integers(1, 6, n)

    int_rate = np.clip(0.05 + 0.028 * grade_q + rng.normal(0, 0.008, n), 0.05, 0.31)
    term_is_60 = rng.random(n) < _sigmoid(0.8 * (grade_q - 3.0))
    loan_amnt = np.clip(_lognormal(rng, 9.45, 0.55, n), 1000, 40000).round(-2)
    term_months = np.where(term_is_60, 60, 36)
    monthly_rate = int_rate / 12
    installment = (
        loan_amnt * monthly_rate / (1 - (1 + monthly_rate) ** (-term_months))
    ).round(2)

    annual_inc = np.clip(_lognormal(rng, 11.1, 0.6, n), 4000, 2_000_000).round(0)
    dti = np.clip(rng.normal(18 + 2.2 * grade_q, 8, n), 0, 60).round(2)
    revol_util = np.clip(rng.normal(0.42 + 0.05 * grade_q, 0.25, n), 0, 1.5)

    open_acc = np.clip(rng.poisson(11, n), 1, 60)
    total_acc = open_acc + rng.poisson(12, n)
    mort_acc = rng.poisson(1.4, n)
    pub_rec_bankruptcies = (rng.random(n) < 0.11).astype(float)
    emp_len_idx = rng.integers(0, len(schema.EMP_LENGTHS), n)
    cr_age_days = np.clip(rng.normal(5800, 2600, n), 400, 22000)

    open_il_12m = rng.poisson(0.7, n).astype(float)
    open_il_24m = open_il_12m + rng.poisson(0.8, n)
    max_bal_bc = np.clip(_lognormal(rng, 8.3, 1.0, n), 0, 150_000).round(0)
    num_rev_accts = np.clip(rng.poisson(14, n), 1, 80).astype(float)

    # --- Planted default risk (nonlinear, with interactions) -----------------
    # The deterministic score is scaled so the Bayes-optimal AUC on observable
    # features lands in the reference's headline regime (~0.95, BASELINE.md);
    # at the default signal_scale an sklearn HistGBT oracle measures ~0.96
    # test AUC and ~21% positive rate on 20k rows.
    z_core = (
        9.0 * (int_rate - 0.13)
        + 0.035 * (dti - 18)
        + 0.9 * (revol_util - 0.45)
        + 0.55 * term_is_60
        - 0.011 * (fico_low - 695)
        - 0.020 * (last_fico_high - fico_high + 20)  # strong distress signal
        + 0.25 * pub_rec_bankruptcies
        - 0.00003 * (cr_age_days - 5800) / 365 * 30
        + 0.35 * ((dti > 32) & (revol_util > 0.8))  # interaction cliff
        + 0.30 * ((last_fico_high < 620).astype(float))
        - 0.08 * np.log1p(annual_inc / 1000)
        + 0.08 * np.log1p(loan_amnt / 1000)
    )
    # Center z_core (empirical mean ~0.65) so scaling it does not shift the
    # logit mean. The base rate still drifts with signal_scale (E[sigmoid]
    # depends on logit variance): ~20% — the LendingClub regime — holds at
    # the default scale, not at arbitrary scales.
    z = (
        -4.1
        + signal_scale * (z_core - 0.65)
        + rng.normal(0, 0.55, n)  # irreducible noise keeps AUC < 1
    )
    default = (rng.random(n) < _sigmoid(z)).astype(int)

    # loan_status covering every key of LOAN_STATUS_MAP (feature_engineering.py:85-94)
    pos_states = ["Charged Off", "Default", "Late (31-120 days)"]
    neg_states = ["Fully Paid", "Current", "Issued", "In Grace Period",
                  "Late (16-30 days)"]
    status = np.where(
        default == 1,
        rng.choice(pos_states, n, p=[0.78, 0.05, 0.17]),
        rng.choice(neg_states, n, p=[0.55, 0.40, 0.01, 0.03, 0.01]),
    )

    # --- Post-origination / leakage columns (must be dropped by the pipeline) -
    paid_frac = np.where(default == 1, rng.beta(1.2, 3.0, n), rng.beta(6, 1.5, n))
    total_pymnt = (loan_amnt * (1 + int_rate) * paid_frac).round(2)
    recoveries = np.where(default == 1, loan_amnt * rng.beta(1.1, 8, n), 0.0).round(2)

    def _date_str(days_ago: np.ndarray) -> np.ndarray:
        base = np.datetime64("2020-09-01")
        dates = base - days_ago.astype("timedelta64[D]")
        y = dates.astype("datetime64[Y]").astype(int) + 1970
        m = dates.astype("datetime64[M]").astype(int) % 12
        return np.array([f"{_MONTHS[mm]}-{yy}" for mm, yy in zip(m, y)])

    frame = {
        "Unnamed: 0.1": np.arange(n) + 1_000_000,  # second index artifact
        "Unnamed: 0": np.arange(n),
        "id": 10_000_000 + np.arange(n),
        "url": np.array(["https://lendingclub.com/loan/%d" % i for i in range(n)]),
        "title": rng.choice(["Debt consolidation", "Credit card refinancing",
                             "Home improvement", "Other"], n),
        "zip_code": rng.choice(["941xx", "112xx", "606xx", "750xx", "331xx"], n),
        "addr_state": rng.choice(["CA", "NY", "TX", "FL", "IL", "WA"], n),
        "emp_title": rng.choice(["Teacher", "Manager", "Driver", "Nurse", "Engineer",
                                 "Owner", ""], n),
        # ~7% missing like the real table (cell 26: 6,950/100,000) -> the NN
        # path imputes emp_length_num and adds its _NA indicator (cell 18).
        "emp_length": np.where(
            rng.random(n) < 0.07, None,
            np.array(schema.EMP_LENGTHS, dtype=object)[emp_len_idx],
        ),
        "issue_d": _date_str(rng.integers(30, 4000, n).astype(float)),
        "earliest_cr_line": _date_str(cr_age_days),
        "initial_list_status": rng.choice(["w", "f"], n),
        "pymnt_plan": np.where(rng.random(n) < 0.995, "n", "y"),
        "hardship_flag": np.where(rng.random(n) < 0.98, "N", "Y"),
        "grade": np.array(schema.GRADES, dtype=object)[grade_idx],
        "sub_grade": np.array(
            [f"{schema.GRADES[g]}{s}" for g, s in zip(grade_idx, sub)], dtype=object
        ),
        "term": np.where(term_is_60, " 60 months", " 36 months"),
        "int_rate": np.array([f"{r * 100:.2f}%" for r in int_rate]),
        "loan_amnt": loan_amnt,
        "funded_amnt": loan_amnt,
        "funded_amnt_inv": (loan_amnt * rng.uniform(0.97, 1.0, n)).round(2),
        "installment": installment,
        "annual_inc": annual_inc,
        "dti": dti,
        "fico_range_low": fico_low,
        "fico_range_high": fico_high,
        "last_fico_range_high": last_fico_high,
        "last_fico_range_low": np.clip(last_fico_high - 4, 300, 850),
        "revol_util": np.where(
            rng.random(n) < 0.004, None,
            np.array([f"{u * 100:.1f}%" for u in revol_util], dtype=object),
        ),
        "revol_bal": np.clip(_lognormal(rng, 9.2, 1.1, n), 0, 500_000).round(0),
        "open_acc": open_acc.astype(float),
        "total_acc": total_acc.astype(float),
        "mort_acc": mort_acc.astype(float),
        "pub_rec": (pub_rec_bankruptcies + (rng.random(n) < 0.05)).round(0),
        "pub_rec_bankruptcies": pub_rec_bankruptcies,
        # open_il_12m/open_il_24m/max_bal_bc/num_rev_accts join the blocked
        # updates below (shared-missingness structure).
        "loan_status": status,
        "application_type": rng.choice(schema.APPLICATION_TYPES, n, p=[0.95, 0.05]),
        "home_ownership": rng.choice(schema.HOME_OWNERSHIP, n,
                                     p=[0.49, 0.39, 0.11, 0.004, 0.004, 0.002]),
        "verification_status": rng.choice(schema.VERIFICATION_STATUS, n),
        "purpose": rng.choice(schema.PURPOSES, n),
        # Leakage block (FE_LEAKAGE_COLS + TRAIN_LEAKAGE_COLS)
        "recoveries": recoveries,
        "collection_recovery_fee": (recoveries * 0.18).round(2),
        "debt_settlement_flag": np.where(default == 1,
                                         np.where(rng.random(n) < 0.3, "Y", "N"), "N"),
        "total_pymnt": total_pymnt,
        "total_pymnt_inv": (total_pymnt * rng.uniform(0.97, 1.0, n)).round(2),
        "total_rec_prncp": (total_pymnt * rng.uniform(0.6, 0.95, n)).round(2),
        "total_rec_int": (total_pymnt * rng.uniform(0.05, 0.4, n)).round(2),
        "total_rec_late_fee": np.where(default == 1,
                                       rng.exponential(8, n), 0.0).round(2),
        "last_pymnt_amnt": (installment * rng.uniform(0.5, 30, n)).round(2),
        "last_pymnt_d": _date_str(rng.integers(10, 2000, n).astype(float)),
        "next_pymnt_d": _date_str(-rng.integers(5, 40, n).astype(float)),
        "last_credit_pull_d": _date_str(rng.integers(1, 400, n).astype(float)),
        "out_prncp": (loan_amnt * (1 - paid_frac)).round(2),
        "out_prncp_inv": (loan_amnt * (1 - paid_frac) * 0.99).round(2),
        # Extra numerics from the log-transform list (feature_engineering.py:118-130)
        "acc_now_delinq": rng.poisson(0.02, n).astype(float),
        "delinq_2yrs": rng.poisson(0.3, n).astype(float),
        "inq_last_6mths": rng.poisson(0.6, n).astype(float),
        # Dense low-information columns present in the raw table
        # (01_data_cleaning.ipynb cell 26: 0 nulls).
        "policy_code": np.ones(n),
        "delinq_amnt": np.where(rng.random(n) < 0.01,
                                _lognormal(rng, 7, 1, n), 0.0).round(0),
        "collections_12_mths_ex_med": rng.poisson(0.02, n).astype(float),
        "tax_liens": rng.poisson(0.05, n).astype(float),
        # hardship_status: mostly missing → filled "No Hardship" (clean_data.py:116-118)
        "hardship_status": np.where(
            rng.random(n) < 0.95, None,
            rng.choice(["ACTIVE", "BROKEN", "COMPLETE", "COMPLETED"], n)),
    }

    # --- Bureau-history block (shared ~2.4% missingness) ---------------------
    # In the real table (01_data_cleaning.ipynb cell 26) a ~2.4% row subset
    # misses the whole credit-bureau block at once; those rows then miss >20
    # columns and are dropped by the row-null allowance
    # (feature_engineering.py:66) — 99,995 -> 97,557 rows. Reproducing the
    # BLOCK structure (one shared mask, nested sub-blocks) reproduces that
    # row-drop behavior; independent per-column masks would not.
    m_core = rng.random(n) < 0.0244
    m_sats = m_core & (rng.random(n) < 0.84)  # num_bc_sats/num_sats subset
    m_1778 = m_sats & (rng.random(n) < 0.87)  # acc_open.../mort_acc subset

    def _blocked_col(vals: np.ndarray, mask: np.ndarray) -> np.ndarray:
        return np.where(mask, np.nan, vals)

    frame.update({
        "tot_coll_amt": _blocked_col(
            np.where(rng.random(n) < 0.12,
                     _lognormal(rng, 6, 1.3, n), 0.0).round(0), m_core),
        "tot_cur_bal": _blocked_col(
            np.clip(_lognormal(rng, 11.4, 1.0, n), 0, 3e6).round(0), m_core),
        "total_rev_hi_lim": _blocked_col(
            np.clip(_lognormal(rng, 10.1, 0.9, n), 0, 1e6).round(0), m_core),
        "mo_sin_old_rev_tl_op": _blocked_col(
            np.clip(rng.normal(180, 90, n), 2, 800).round(0), m_core),
        "mo_sin_rcnt_rev_tl_op": _blocked_col(
            rng.exponential(14, n).round(0), m_core),
        "mo_sin_rcnt_tl": _blocked_col(rng.exponential(8, n).round(0), m_core),
        "num_accts_ever_120_pd": _blocked_col(
            rng.poisson(0.5, n).astype(float), m_core),
        "num_actv_bc_tl": _blocked_col(rng.poisson(3.7, n).astype(float), m_core),
        "num_actv_rev_tl": _blocked_col(rng.poisson(5.6, n).astype(float), m_core),
        "num_bc_tl": _blocked_col(rng.poisson(7.7, n).astype(float), m_core),
        "num_il_tl": _blocked_col(rng.poisson(8.4, n).astype(float), m_core),
        "num_op_rev_tl": _blocked_col(rng.poisson(8.2, n).astype(float), m_core),
        "num_rev_accts": _blocked_col(num_rev_accts, m_core),
        "num_rev_tl_bal_gt_0": _blocked_col(
            rng.poisson(5.6, n).astype(float), m_core),
        "num_tl_30dpd": _blocked_col(rng.poisson(0.03, n).astype(float), m_core),
        "num_tl_90g_dpd_24m": _blocked_col(
            rng.poisson(0.08, n).astype(float), m_core),
        "num_tl_op_past_12m": _blocked_col(
            rng.poisson(2.1, n).astype(float), m_core),
        "tot_hi_cred_lim": _blocked_col(
            np.clip(_lognormal(rng, 11.8, 0.9, n), 0, 4e6).round(0), m_core),
        "total_il_high_credit_limit": _blocked_col(
            np.clip(_lognormal(rng, 10.4, 1.0, n), 0, 1.5e6).round(0), m_core),
        "num_bc_sats": _blocked_col(rng.poisson(4.7, n).astype(float), m_sats),
        "num_sats": _blocked_col(rng.poisson(11.6, n).astype(float), m_sats),
        "acc_open_past_24mths": _blocked_col(
            rng.poisson(4, n).astype(float), m_1778),
        "total_bal_ex_mort": _blocked_col(
            np.clip(_lognormal(rng, 10.6, 0.9, n), 0, 1.5e6).round(0), m_1778),
        "total_bc_limit": _blocked_col(
            np.clip(_lognormal(rng, 9.7, 1.0, n), 0, 6e5).round(0), m_1778),
        # Core-block members with small extra independent missingness, so the
        # NN path still sees surviving NaNs (-> _NA indicators, cell 18) after
        # the core rows are dropped.
        "avg_cur_bal": _blocked_col(
            np.clip(_lognormal(rng, 9.1, 1.0, n), 0, 5e5).round(0),
            m_core | (rng.random(n) < 0.005)),
        "bc_open_to_buy": _blocked_col(
            np.clip(_lognormal(rng, 8.8, 1.3, n), 0, 4e5).round(0),
            m_core | (rng.random(n) < 0.005)),
        "pct_tl_nvr_dlq": _blocked_col(
            np.clip(rng.normal(94, 8, n), 20, 100).round(1),
            m_core | (rng.random(n) < 0.005)),
        "percent_bc_gt_75": _blocked_col(
            np.clip(rng.normal(40, 34, n), 0, 100).round(1),
            m_core | (rng.random(n) < 0.005)),
        "bc_util": _blocked_col(
            np.clip(rng.normal(57, 28, n), 0, 200).round(1),
            m_core | (rng.random(n) < 0.005)),
        "mo_sin_old_il_acct": _blocked_col(
            np.clip(rng.normal(130, 60, n), 1, 600).round(0),
            m_core | (rng.random(n) < 0.03)),
        "num_tl_120dpd_2m": _blocked_col(
            rng.poisson(0.01, n).astype(float),
            m_core | (rng.random(n) < 0.03)),
    })

    # --- Installment/revolving detail block (shared ~29.6% missingness) ------
    # Pre-2015 originations lack these fields entirely, so they go missing
    # TOGETHER (cell 26: 29,644 nulls across the whole block). Survivors of
    # the row-null allowance keep these NaNs -> imputed + _NA indicators on
    # the NN path (03_feature_engineering.ipynb cell 18).
    m_il = rng.random(n) < 0.296
    frame.update({
        "open_act_il": _blocked_col(rng.poisson(2.4, n).astype(float), m_il),
        "open_il_12m": _blocked_col(open_il_12m, m_il),
        "open_il_24m": _blocked_col(open_il_24m.astype(float), m_il),
        "mths_since_rcnt_il": _blocked_col(
            rng.exponential(16, n).round(0), m_il),
        "total_bal_il": _blocked_col(
            np.clip(_lognormal(rng, 10.0, 1.1, n), 0, 1e6).round(0), m_il),
        "open_rv_12m": _blocked_col(rng.poisson(1.3, n).astype(float), m_il),
        "open_rv_24m": _blocked_col(rng.poisson(2.5, n).astype(float), m_il),
        "max_bal_bc": _blocked_col(max_bal_bc, m_il),
        "inq_fi": _blocked_col(rng.poisson(1.1, n).astype(float), m_il),
        "total_cu_tl": _blocked_col(rng.poisson(1.5, n).astype(float), m_il),
        # FILL_ZERO_COLS ride the same block (clean_data.py:140 fills them).
        "inq_last_12m": _blocked_col(rng.poisson(2, n).astype(float), m_il),
        "open_acc_6m": _blocked_col(rng.poisson(1, n).astype(float), m_il),
        "chargeoff_within_12_mths": np.where(rng.random(n) < 0.05, np.nan, 0.0),
        # il_util/all_util: the block plus extra (cell 26: 39.7% / 29.7%) —
        # both dropped as "unnecessary" during cleaning either way.
        "il_util": _blocked_col(
            rng.normal(0.7, 0.2, n).round(3), m_il | (rng.random(n) < 0.14)),
        "all_util": _blocked_col(rng.normal(0.6, 0.2, n).round(3), m_il),
    })

    # --- Moderately sparse month-since columns (independent missingness) -----
    frame.update({
        "mths_since_last_delinq": np.where(rng.random(n) < 0.5, np.nan,
                                           rng.exponential(34, n).round(0)),
        "mths_since_recent_bc": np.where(rng.random(n) < 0.1, np.nan,
                                         rng.exponential(25, n).round(0)),
        "mths_since_recent_inq": np.where(rng.random(n) < 0.13, np.nan,
                                          rng.exponential(7, n).round(0)),
        "mths_since_recent_revol_delinq": np.where(
            rng.random(n) < 0.67, np.nan, rng.exponential(35, n).round(0)),
        "mths_since_recent_bc_dlq": np.where(
            rng.random(n) < 0.77, np.nan, rng.exponential(39, n).round(0)),
    })

    # --- >70%-null blocks the cleaner must drop (clean_data.py:31-41) --------
    # Joint-application, secondary-applicant and hardship-detail blocks, plus
    # two very sparse month-since columns — all present in the raw table and
    # all above the 70% null threshold (cell 26 / cell 28).
    frame.update({
        "mths_since_last_record": np.where(
            rng.random(n) < 0.854, np.nan, rng.exponential(75, n).round(0)),
        "mths_since_last_major_derog": np.where(
            rng.random(n) < 0.754, np.nan, rng.exponential(44, n).round(0)),
    })
    m_joint = rng.random(n) < 0.928
    frame.update({
        "annual_inc_joint": _blocked_col(
            np.clip(_lognormal(rng, 11.6, 0.5, n), 1e4, 3e6).round(0), m_joint),
        "dti_joint": _blocked_col(
            np.clip(rng.normal(19, 7, n), 0, 60).round(2), m_joint),
        "verification_status_joint": np.where(
            m_joint, None, rng.choice(schema.VERIFICATION_STATUS, n)),
        "revol_bal_joint": _blocked_col(
            np.clip(_lognormal(rng, 9.8, 1.0, n), 0, 6e5).round(0),
            m_joint | (rng.random(n) < 0.06)),
    })
    m_sec = rng.random(n) < 0.9326
    frame.update({
        "sec_app_fico_range_low": _blocked_col(
            np.clip(rng.normal(690, 35, n), 630, 845).round(0), m_sec),
        "sec_app_fico_range_high": _blocked_col(
            np.clip(rng.normal(694, 35, n), 634, 849).round(0), m_sec),
        "sec_app_earliest_cr_line": np.where(
            m_sec, None, _date_str(np.clip(rng.normal(5400, 2400, n), 400, 20000))),
        "sec_app_inq_last_6mths": _blocked_col(
            rng.poisson(0.7, n).astype(float), m_sec),
        "sec_app_mort_acc": _blocked_col(
            rng.poisson(1.2, n).astype(float), m_sec),
        "sec_app_open_acc": _blocked_col(
            rng.poisson(11, n).astype(float), m_sec),
        "sec_app_revol_util": _blocked_col(
            np.clip(rng.normal(0.5, 0.25, n), 0, 1.5).round(3),
            m_sec | (rng.random(n) < 0.02)),
        "sec_app_open_act_il": _blocked_col(
            rng.poisson(2.5, n).astype(float), m_sec),
        "sec_app_num_rev_accts": _blocked_col(
            rng.poisson(13, n).astype(float), m_sec),
        "sec_app_chargeoff_within_12_mths": _blocked_col(
            rng.poisson(0.03, n).astype(float), m_sec),
        "sec_app_collections_12_mths_ex_med": _blocked_col(
            rng.poisson(0.04, n).astype(float), m_sec),
    })
    m_hard = rng.random(n) < 0.951
    # The hardship amount columns are present slightly more often than the
    # rest of the block (93.78% vs 95.1% null, cell 26).
    m_hard_amt = m_hard & (rng.random(n) < 0.986)
    frame.update({
        "hardship_type": np.where(
            m_hard, None, np.array(["INTEREST ONLY-3 MONTHS DEFERRAL"] * n)),
        "hardship_reason": np.where(
            m_hard, None, rng.choice(["NATURAL_DISASTER", "DISABILITY",
                                      "UNEMPLOYMENT", "INCOME_CURTAILMENT"], n)),
        "deferral_term": _blocked_col(np.full(n, 3.0), m_hard),
        "hardship_amount": _blocked_col(
            (installment * rng.uniform(0.1, 0.9, n)).round(2), m_hard_amt),
        "hardship_start_date": np.where(
            m_hard, None, _date_str(rng.integers(100, 1200, n).astype(float))),
        "hardship_end_date": np.where(
            m_hard, None, _date_str(rng.integers(10, 1100, n).astype(float))),
        "payment_plan_start_date": np.where(
            m_hard, None, _date_str(rng.integers(10, 1200, n).astype(float))),
        "hardship_length": _blocked_col(np.full(n, 3.0), m_hard),
        "hardship_dpd": _blocked_col(rng.poisson(12, n).astype(float), m_hard),
        "hardship_loan_status": np.where(
            m_hard | (rng.random(n) < 0.003), None,
            rng.choice(["Late (16-30 days)", "Late (31-120 days)", "Current"], n)),
        "orig_projected_additional_accrued_interest": _blocked_col(
            (installment * rng.uniform(0.05, 0.5, n)).round(2),
            m_hard_amt | (rng.random(n) < 0.002)),
        "hardship_payoff_balance_amount": _blocked_col(
            (loan_amnt * rng.uniform(0.2, 1.0, n)).round(2), m_hard_amt),
        "hardship_last_payment_amount": _blocked_col(
            (installment * rng.uniform(0.1, 1.2, n)).round(2), m_hard_amt),
    })

    df = pd.DataFrame(frame)

    # >70%-null junk columns that the cleaner must drop (clean_data.py:31-41).
    for j in range(missing_junk_cols):
        col = rng.normal(0, 1, n)
        mask = rng.random(n) < 0.9
        df[f"junk_sparse_{j}"] = np.where(mask, np.nan, col)

    # A handful of exact duplicate rows (clean_data.py:146-150).
    n_dup = max(1, int(n * duplicate_fraction))
    df = pd.concat([df, df.iloc[:n_dup]], ignore_index=True)
    return df
