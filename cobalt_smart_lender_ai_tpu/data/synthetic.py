"""Synthetic LendingClub-schema data generator.

The reference's raw data lives behind DVC pointers to a private S3 bucket
(`data/1-raw/lending-club-2007-2020Q3/*.dvc`) and cannot be fetched offline.
This module generates a raw frame with the same observable schema the pipeline
consumes — including the string quirks the cleaning stage must handle
(`" 36 months"`, `"13.56%"`, `"Apr-2005"`, `"10+ years"`, `"< 1 year"`),
`Unnamed: 0` index artifacts, >70%-null junk columns, duplicate rows, and a
`loan_status` column covering every key of the label map
(`feature_engineering.py:85-94`).

The default label is planted as a Bernoulli draw from a nonlinear
risk score over fico / dti / int_rate / grade / term / utilization with
interactions, so tree models meaningfully beat linear ones and tuned models can
reach the reference's headline AUC regime (~0.95, BASELINE.md).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from cobalt_smart_lender_ai_tpu.data import schema

_MONTHS = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
           "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")


def _sigmoid(z: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-z))


def _lognormal(rng, mean: float, sigma: float, n: int) -> np.ndarray:
    return rng.lognormal(mean, sigma, n)


def synthetic_lendingclub_frame(
    n_rows: int = 10_000,
    seed: int = 0,
    *,
    missing_junk_cols: int = 3,
    duplicate_fraction: float = 0.002,
    signal_scale: float = 3.5,
) -> pd.DataFrame:
    """Build a raw-schema frame of ``n_rows`` loans (plus a few duplicates)."""
    rng = np.random.default_rng(seed)
    n = n_rows

    # --- Core credit variables with realistic correlation structure ----------
    fico_low = np.clip(rng.normal(695, 32, n), 630, 845).round(0)
    fico_high = fico_low + 4.0
    # last_fico drifts from origination fico; big drops signal distress.
    fico_drift = rng.normal(0, 45, n) - 20 * (rng.random(n) < 0.15)
    last_fico_high = np.clip(fico_high + fico_drift, 300, 850).round(0)

    grade_q = np.clip(
        (850 - fico_low) / 40 + rng.normal(0, 1.0, n), 0, 6.999
    )
    grade_idx = grade_q.astype(int)  # 0..6 → A..G
    sub = rng.integers(1, 6, n)

    int_rate = np.clip(0.05 + 0.028 * grade_q + rng.normal(0, 0.008, n), 0.05, 0.31)
    term_is_60 = rng.random(n) < _sigmoid(0.8 * (grade_q - 3.0))
    loan_amnt = np.clip(_lognormal(rng, 9.45, 0.55, n), 1000, 40000).round(-2)
    term_months = np.where(term_is_60, 60, 36)
    monthly_rate = int_rate / 12
    installment = (
        loan_amnt * monthly_rate / (1 - (1 + monthly_rate) ** (-term_months))
    ).round(2)

    annual_inc = np.clip(_lognormal(rng, 11.1, 0.6, n), 4000, 2_000_000).round(0)
    dti = np.clip(rng.normal(18 + 2.2 * grade_q, 8, n), 0, 60).round(2)
    revol_util = np.clip(rng.normal(0.42 + 0.05 * grade_q, 0.25, n), 0, 1.5)

    open_acc = np.clip(rng.poisson(11, n), 1, 60)
    total_acc = open_acc + rng.poisson(12, n)
    mort_acc = rng.poisson(1.4, n)
    pub_rec_bankruptcies = (rng.random(n) < 0.11).astype(float)
    emp_len_idx = rng.integers(0, len(schema.EMP_LENGTHS), n)
    cr_age_days = np.clip(rng.normal(5800, 2600, n), 400, 22000)

    open_il_12m = rng.poisson(0.7, n).astype(float)
    open_il_24m = open_il_12m + rng.poisson(0.8, n)
    max_bal_bc = np.clip(_lognormal(rng, 8.3, 1.0, n), 0, 150_000).round(0)
    num_rev_accts = np.clip(rng.poisson(14, n), 1, 80).astype(float)

    # --- Planted default risk (nonlinear, with interactions) -----------------
    # The deterministic score is scaled so the Bayes-optimal AUC on observable
    # features lands in the reference's headline regime (~0.95, BASELINE.md);
    # at the default signal_scale an sklearn HistGBT oracle measures ~0.96
    # test AUC and ~21% positive rate on 20k rows.
    z_core = (
        9.0 * (int_rate - 0.13)
        + 0.035 * (dti - 18)
        + 0.9 * (revol_util - 0.45)
        + 0.55 * term_is_60
        - 0.011 * (fico_low - 695)
        - 0.020 * (last_fico_high - fico_high + 20)  # strong distress signal
        + 0.25 * pub_rec_bankruptcies
        - 0.00003 * (cr_age_days - 5800) / 365 * 30
        + 0.35 * ((dti > 32) & (revol_util > 0.8))  # interaction cliff
        + 0.30 * ((last_fico_high < 620).astype(float))
        - 0.08 * np.log1p(annual_inc / 1000)
        + 0.08 * np.log1p(loan_amnt / 1000)
    )
    # Center z_core (empirical mean ~0.65) so scaling it does not shift the
    # logit mean. The base rate still drifts with signal_scale (E[sigmoid]
    # depends on logit variance): ~20% — the LendingClub regime — holds at
    # the default scale, not at arbitrary scales.
    z = (
        -4.1
        + signal_scale * (z_core - 0.65)
        + rng.normal(0, 0.55, n)  # irreducible noise keeps AUC < 1
    )
    default = (rng.random(n) < _sigmoid(z)).astype(int)

    # loan_status covering every key of LOAN_STATUS_MAP (feature_engineering.py:85-94)
    pos_states = ["Charged Off", "Default", "Late (31-120 days)"]
    neg_states = ["Fully Paid", "Current", "Issued", "In Grace Period",
                  "Late (16-30 days)"]
    status = np.where(
        default == 1,
        rng.choice(pos_states, n, p=[0.78, 0.05, 0.17]),
        rng.choice(neg_states, n, p=[0.55, 0.40, 0.01, 0.03, 0.01]),
    )

    # --- Post-origination / leakage columns (must be dropped by the pipeline) -
    paid_frac = np.where(default == 1, rng.beta(1.2, 3.0, n), rng.beta(6, 1.5, n))
    total_pymnt = (loan_amnt * (1 + int_rate) * paid_frac).round(2)
    recoveries = np.where(default == 1, loan_amnt * rng.beta(1.1, 8, n), 0.0).round(2)

    def _date_str(days_ago: np.ndarray) -> np.ndarray:
        base = np.datetime64("2020-09-01")
        dates = base - days_ago.astype("timedelta64[D]")
        y = dates.astype("datetime64[Y]").astype(int) + 1970
        m = dates.astype("datetime64[M]").astype(int) % 12
        return np.array([f"{_MONTHS[mm]}-{yy}" for mm, yy in zip(m, y)])

    frame = {
        "Unnamed: 0": np.arange(n),
        "id": 10_000_000 + np.arange(n),
        "url": np.array(["https://lendingclub.com/loan/%d" % i for i in range(n)]),
        "title": rng.choice(["Debt consolidation", "Credit card refinancing",
                             "Home improvement", "Other"], n),
        "zip_code": rng.choice(["941xx", "112xx", "606xx", "750xx", "331xx"], n),
        "addr_state": rng.choice(["CA", "NY", "TX", "FL", "IL", "WA"], n),
        "emp_title": rng.choice(["Teacher", "Manager", "Driver", "Nurse", "Engineer",
                                 "Owner", ""], n),
        "emp_length": np.array(schema.EMP_LENGTHS, dtype=object)[emp_len_idx],
        "issue_d": _date_str(rng.integers(30, 4000, n).astype(float)),
        "earliest_cr_line": _date_str(cr_age_days),
        "initial_list_status": rng.choice(["w", "f"], n),
        "pymnt_plan": np.where(rng.random(n) < 0.995, "n", "y"),
        "hardship_flag": np.where(rng.random(n) < 0.98, "N", "Y"),
        "grade": np.array(schema.GRADES, dtype=object)[grade_idx],
        "sub_grade": np.array(
            [f"{schema.GRADES[g]}{s}" for g, s in zip(grade_idx, sub)], dtype=object
        ),
        "term": np.where(term_is_60, " 60 months", " 36 months"),
        "int_rate": np.array([f"{r * 100:.2f}%" for r in int_rate]),
        "loan_amnt": loan_amnt,
        "funded_amnt": loan_amnt,
        "funded_amnt_inv": (loan_amnt * rng.uniform(0.97, 1.0, n)).round(2),
        "installment": installment,
        "annual_inc": annual_inc,
        "dti": dti,
        "fico_range_low": fico_low,
        "fico_range_high": fico_high,
        "last_fico_range_high": last_fico_high,
        "last_fico_range_low": np.clip(last_fico_high - 4, 300, 850),
        "revol_util": np.array([f"{u * 100:.1f}%" for u in revol_util], dtype=object),
        "revol_bal": np.clip(_lognormal(rng, 9.2, 1.1, n), 0, 500_000).round(0),
        "open_acc": open_acc.astype(float),
        "total_acc": total_acc.astype(float),
        "mort_acc": mort_acc.astype(float),
        "pub_rec": (pub_rec_bankruptcies + (rng.random(n) < 0.05)).round(0),
        "pub_rec_bankruptcies": pub_rec_bankruptcies,
        "open_il_12m": open_il_12m,
        "open_il_24m": open_il_24m,
        "max_bal_bc": max_bal_bc,
        "num_rev_accts": num_rev_accts,
        "loan_status": status,
        "application_type": rng.choice(schema.APPLICATION_TYPES, n, p=[0.95, 0.05]),
        "home_ownership": rng.choice(schema.HOME_OWNERSHIP, n,
                                     p=[0.49, 0.39, 0.11, 0.004, 0.004, 0.002]),
        "verification_status": rng.choice(schema.VERIFICATION_STATUS, n),
        "purpose": rng.choice(schema.PURPOSES, n),
        # Leakage block (FE_LEAKAGE_COLS + TRAIN_LEAKAGE_COLS)
        "recoveries": recoveries,
        "collection_recovery_fee": (recoveries * 0.18).round(2),
        "debt_settlement_flag": np.where(default == 1,
                                         np.where(rng.random(n) < 0.3, "Y", "N"), "N"),
        "total_pymnt": total_pymnt,
        "total_pymnt_inv": (total_pymnt * rng.uniform(0.97, 1.0, n)).round(2),
        "total_rec_prncp": (total_pymnt * rng.uniform(0.6, 0.95, n)).round(2),
        "total_rec_int": (total_pymnt * rng.uniform(0.05, 0.4, n)).round(2),
        "total_rec_late_fee": np.where(default == 1,
                                       rng.exponential(8, n), 0.0).round(2),
        "last_pymnt_amnt": (installment * rng.uniform(0.5, 30, n)).round(2),
        "last_pymnt_d": _date_str(rng.integers(10, 2000, n).astype(float)),
        "next_pymnt_d": _date_str(-rng.integers(5, 40, n).astype(float)),
        "last_credit_pull_d": _date_str(rng.integers(1, 400, n).astype(float)),
        "out_prncp": (loan_amnt * (1 - paid_frac)).round(2),
        "out_prncp_inv": (loan_amnt * (1 - paid_frac) * 0.99).round(2),
        # Extra numerics from the log-transform list (feature_engineering.py:118-130)
        "acc_now_delinq": rng.poisson(0.02, n).astype(float),
        "tot_coll_amt": np.where(rng.random(n) < 0.12,
                                 _lognormal(rng, 6, 1.3, n), 0.0).round(0),
        "tot_cur_bal": np.clip(_lognormal(rng, 11.4, 1.0, n), 0, 3e6).round(0),
        "total_rev_hi_lim": np.clip(_lognormal(rng, 10.1, 0.9, n), 0, 1e6).round(0),
        "acc_open_past_24mths": rng.poisson(4, n).astype(float),
        "avg_cur_bal": np.clip(_lognormal(rng, 9.1, 1.0, n), 0, 5e5).round(0),
        "bc_open_to_buy": np.clip(_lognormal(rng, 8.8, 1.3, n), 0, 4e5).round(0),
        "mo_sin_old_rev_tl_op": np.clip(rng.normal(180, 90, n), 2, 800).round(0),
        "mo_sin_rcnt_rev_tl_op": rng.exponential(14, n).round(0),
        "mo_sin_rcnt_tl": rng.exponential(8, n).round(0),
        "num_accts_ever_120_pd": rng.poisson(0.5, n).astype(float),
        "num_actv_bc_tl": rng.poisson(3.7, n).astype(float),
        "num_actv_rev_tl": rng.poisson(5.6, n).astype(float),
        "num_bc_sats": rng.poisson(4.7, n).astype(float),
        "num_bc_tl": rng.poisson(7.7, n).astype(float),
        "num_il_tl": rng.poisson(8.4, n).astype(float),
        "num_op_rev_tl": rng.poisson(8.2, n).astype(float),
        "num_rev_tl_bal_gt_0": rng.poisson(5.6, n).astype(float),
        "num_sats": rng.poisson(11.6, n).astype(float),
        "num_tl_op_past_12m": rng.poisson(2.1, n).astype(float),
        "tot_hi_cred_lim": np.clip(_lognormal(rng, 11.8, 0.9, n), 0, 4e6).round(0),
        "total_bal_ex_mort": np.clip(_lognormal(rng, 10.6, 0.9, n), 0, 1.5e6).round(0),
        "total_bc_limit": np.clip(_lognormal(rng, 9.7, 1.0, n), 0, 6e5).round(0),
        "total_il_high_credit_limit": np.clip(
            _lognormal(rng, 10.4, 1.0, n), 0, 1.5e6).round(0),
        "pct_tl_nvr_dlq": np.clip(rng.normal(94, 8, n), 20, 100).round(1),
        "percent_bc_gt_75": np.clip(rng.normal(40, 34, n), 0, 100).round(1),
        "delinq_2yrs": rng.poisson(0.3, n).astype(float),
        "inq_last_6mths": rng.poisson(0.6, n).astype(float),
        # Columns cleaned by FILL_ZERO_COLS (clean_data.py:140) — inject NaNs.
        "inq_last_12m": np.where(rng.random(n) < 0.3, np.nan,
                                 rng.poisson(2, n).astype(float)),
        "open_acc_6m": np.where(rng.random(n) < 0.3, np.nan,
                                rng.poisson(1, n).astype(float)),
        "chargeoff_within_12_mths": np.where(rng.random(n) < 0.05, np.nan, 0.0),
        # Sparse columns with moderate missingness (exercise NaN-aware GBDT).
        "mths_since_last_delinq": np.where(rng.random(n) < 0.5, np.nan,
                                           rng.exponential(34, n).round(0)),
        "mths_since_recent_bc": np.where(rng.random(n) < 0.1, np.nan,
                                         rng.exponential(25, n).round(0)),
        "mths_since_recent_inq": np.where(rng.random(n) < 0.13, np.nan,
                                          rng.exponential(7, n).round(0)),
        "mths_since_recent_revol_delinq": np.where(
            rng.random(n) < 0.67, np.nan, rng.exponential(35, n).round(0)),
        "mths_since_recent_bc_dlq": np.where(
            rng.random(n) < 0.77, np.nan, rng.exponential(39, n).round(0)),
        "il_util": np.where(rng.random(n) < 0.75, np.nan,
                            rng.normal(0.7, 0.2, n).round(3)),
        "all_util": np.where(rng.random(n) < 0.75, np.nan,
                             rng.normal(0.6, 0.2, n).round(3)),
        # hardship_status: mostly missing → filled "No Hardship" (clean_data.py:116-118)
        "hardship_status": np.where(
            rng.random(n) < 0.95, None,
            rng.choice(["ACTIVE", "BROKEN", "COMPLETE", "COMPLETED"], n)),
    }

    df = pd.DataFrame(frame)

    # >70%-null junk columns that the cleaner must drop (clean_data.py:31-41).
    for j in range(missing_junk_cols):
        col = rng.normal(0, 1, n)
        mask = rng.random(n) < 0.9
        df[f"junk_sparse_{j}"] = np.where(mask, np.nan, col)

    # A handful of exact duplicate rows (clean_data.py:146-150).
    n_dup = max(1, int(n * duplicate_fraction))
    df = pd.concat([df, df.iloc[:n_dup]], ignore_index=True)
    return df
