"""Feature-engineering stage — capability match for
`src/data_preprocessing/feature_engineering.py`.

Host does only string parsing and vocabulary discovery. The O(N·F) numeric work
— log1p over the ~50 skewed columns, median impute, missing indicators, one-hot
expansion — runs as jitted ops on a device-resident `(N, F)` float32 matrix.
(The reference's hottest construct is a row-wise Python `.apply` log1p loop,
feature_engineering.py:134-139; here it is one fused XLA elementwise op.)

Two outputs, as in the reference (feature_engineering.py:103-184):
  * tree frame — one-hot encoded categoricals (pandas get_dummies drop_first
    semantics: sorted vocabulary, first category dropped), NaNs preserved for
    the NaN-aware GBDT;
  * nn frame — median impute + `<col>_NA` indicators + `no_income`/`dti_NA`
    specials + integer label codes for remaining categoricals.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.data.clean import parse_percent


@dataclasses.dataclass(frozen=True)
class FeatureFrame:
    """A named, device-resident feature matrix."""

    feature_names: tuple[str, ...]
    X: jax.Array  # (N, F) float32
    y: jax.Array | None = None  # (N,) float32 labels

    @property
    def n_rows(self) -> int:
        return int(self.X.shape[0])

    @property
    def n_features(self) -> int:
        return int(self.X.shape[1])

    def column(self, name: str) -> jax.Array:
        return self.X[:, self.feature_names.index(name)]

    def select(self, names: Sequence[str]) -> "FeatureFrame":
        idx = np.array([self.feature_names.index(n) for n in names])
        return FeatureFrame(tuple(names), self.X[:, idx], self.y)

    def drop(self, names: Sequence[str]) -> "FeatureFrame":
        keep = [n for n in self.feature_names if n not in set(names)]
        return self.select(keep)

    def to_pandas(self) -> pd.DataFrame:
        df = pd.DataFrame(np.asarray(self.X), columns=list(self.feature_names))
        if self.y is not None:
            df[schema.LABEL_COL] = np.asarray(self.y)
        return df


@dataclasses.dataclass(frozen=True)
class FeaturePlan:
    """Everything needed to replay the engineering on new raw rows: discovered
    categorical vocabularies and the imputation medians. Versioned alongside
    model artifacts (the reference only gestures at this with
    `selected_features_tree.txt`, model_tree_train_test.py:224-230)."""

    numeric_names: tuple[str, ...]
    categorical_vocab: Mapping[str, tuple[str, ...]]
    label_vocab: Mapping[str, tuple[str, ...]]
    medians: Mapping[str, float]
    log_cols: tuple[str, ...]
    tree_feature_names: tuple[str, ...]
    nn_feature_names: tuple[str, ...]
    #: ISO date the ingest snapshot used for date -> age features; serve-time
    #: replay (`transform_raw_rows`) pins its "today" to this so an artifact
    #: scores a raw row identically no matter when the request arrives.
    asof: str | None = None


def prepare_cleaned_frame(
    df: pd.DataFrame,
    *,
    today: datetime | None = None,
    row_null_allowance: int = 20,
) -> pd.DataFrame:
    """Equivalent of `clean_lending_data` (feature_engineering.py:44-101):
    leakage/useless drop, row-null threshold, emp_length -> numeric,
    revol_util -> ratio, earliest_cr_line -> age in days, label mapping."""
    df = df.drop(
        columns=list(schema.FE_LEAKAGE_COLS) + list(schema.FE_USELESS_COLS),
        errors="ignore",
    )
    df = df.dropna(thresh=df.shape[1] - row_null_allowance)

    if "emp_length" in df.columns:
        emp = df["emp_length"].replace("< 1 year", "0")
        df = df.assign(
            emp_length_num=pd.to_numeric(
                emp.str.extract(r"(\d+)")[0], errors="coerce"
            )
        ).drop(columns=["emp_length"])

    if "revol_util" in df.columns and not pd.api.types.is_numeric_dtype(df["revol_util"]):
        df = df.assign(revol_util=parse_percent(df["revol_util"]))

    if "earliest_cr_line" in df.columns:
        now = today or datetime.today()
        dates = pd.to_datetime(df["earliest_cr_line"], format="%b-%Y", errors="coerce")
        df = df.assign(earliest_cr_line_days=(now - dates).dt.days).drop(
            columns=["earliest_cr_line"]
        )

    if "loan_status" in df.columns:
        df = df.assign(
            **{schema.LABEL_COL: df["loan_status"].map(schema.LOAN_STATUS_MAP)}
        ).drop(columns=["loan_status"])

    return df.reset_index(drop=True)


# --- Device-side numeric transforms ------------------------------------------


# The plain (un-jitted) bodies are shared with `data/device_pipeline.py`,
# which traces them inside its own fused `ingest.*` programs: both the pandas
# path and the device path run the *same code objects*, so the two feature
# matrices cannot drift apart by construction.


def log1p_masked(X: jax.Array, col_mask: jax.Array) -> jax.Array:
    """log1p on masked columns where value is present and positive
    (elementwise-equivalent to feature_engineering.py:134-139)."""
    apply = col_mask[None, :] & (X > 0) & ~jnp.isnan(X)
    return jnp.where(apply, jnp.log1p(X), X)


def one_hot_codes(codes: jax.Array, n_classes: int) -> jax.Array:
    """(N,) int32 codes -> (N, n_classes-1) one-hot, dropping class 0
    (get_dummies drop_first=True; code -1 == missing -> all-zero row)."""
    return (codes[:, None] == jnp.arange(1, n_classes)[None, :]).astype(jnp.float32)


def impute_with_indicators(X: jax.Array, medians: jax.Array, need: jax.Array):
    """Median-fill NaNs; return filled matrix + per-column indicator block for
    the columns flagged in ``need`` (feature_engineering.py:156-162)."""
    isnan = jnp.isnan(X)
    filled = jnp.where(isnan, medians[None, :], X)
    indicators = jnp.where(need[None, :], isnan.astype(jnp.float32), 0.0)
    return filled, indicators


_log1p_masked = jax.jit(log1p_masked)
_one_hot_codes = partial(jax.jit, static_argnames=("n_classes",))(one_hot_codes)
_impute_with_indicators = jax.jit(impute_with_indicators)


def engineer_features(
    df: pd.DataFrame,
    *,
    one_hot_cols: Sequence[str] = schema.ONE_HOT_COLS,
    log_cols: Sequence[str] = schema.LOG_COLS,
) -> tuple[FeatureFrame, FeatureFrame, FeaturePlan]:
    """Build the tree and nn feature frames from a prepared frame."""
    y = None
    if schema.LABEL_COL in df.columns:
        y = jnp.asarray(df[schema.LABEL_COL].to_numpy(np.float32))
        df = df.drop(columns=[schema.LABEL_COL])

    cat_present = [c for c in one_hot_cols if c in df.columns]
    numeric_df = df.drop(columns=cat_present)
    # Any other residual object columns are label-encoded in both frames
    # (feature_engineering.py:170-176 does this for the nn frame; the tree frame
    # in the reference would carry them as objects — we encode for usability).
    residual_obj = [
        c for c in numeric_df.columns if not pd.api.types.is_numeric_dtype(numeric_df[c])
    ]
    label_vocab: dict[str, tuple[str, ...]] = {}
    for c in residual_obj:
        vals = numeric_df[c].astype(str).fillna("missing")
        vocab = tuple(sorted(vals.unique()))
        lookup = {v: i for i, v in enumerate(vocab)}
        numeric_df = numeric_df.assign(**{c: vals.map(lookup).astype(np.float32)})
        label_vocab[c] = vocab

    numeric_names = tuple(numeric_df.columns)
    X_num = jnp.asarray(numeric_df.to_numpy(np.float32))

    # log1p on device
    log_mask = jnp.asarray(np.isin(np.array(numeric_names), np.array(log_cols)))
    X_num = _log1p_masked(X_num, log_mask)

    # --- tree frame: one-hot categoricals -------------------------------
    vocab: dict[str, tuple[str, ...]] = {}
    tree_blocks = [X_num]
    tree_names = list(numeric_names)
    for c in cat_present:
        vals = df[c]
        cats = tuple(sorted(v for v in vals.dropna().unique()))
        vocab[c] = cats
        lookup = {v: i for i, v in enumerate(cats)}
        codes = jnp.asarray(
            vals.map(lookup).fillna(-1).to_numpy(np.int32)
        )
        if len(cats) > 1:
            tree_blocks.append(_one_hot_codes(codes, len(cats)))
            tree_names.extend(f"{c}_{v}" for v in cats[1:])
    X_tree = jnp.concatenate(tree_blocks, axis=1)

    # --- nn frame: impute + indicators + label codes ---------------------
    # NaN detection + medians run on device; only the (F,) bool mask comes
    # back to host (it drives Python-level column-list construction).
    nan_any = np.asarray(jnp.any(jnp.isnan(X_num), axis=0))
    dti_idx = numeric_names.index("dti") if "dti" in numeric_names else -1
    need_ind = nan_any.copy()
    if dti_idx >= 0:
        need_ind[dti_idx] = False  # dti handled specially below
    medians = jnp.nanmedian(X_num, axis=0)
    medians = jnp.where(jnp.isnan(medians), 0.0, medians)
    X_filled, indicators = _impute_with_indicators(
        X_num, medians, jnp.asarray(need_ind)
    )
    nn_blocks = [X_filled]
    nn_names = list(numeric_names)
    ind_cols = [i for i in range(len(numeric_names)) if need_ind[i]]
    if ind_cols:
        nn_blocks.append(indicators[:, np.array(ind_cols)])
        nn_names.extend(f"{numeric_names[i]}_NA" for i in ind_cols)
    # Specials (feature_engineering.py:164-167)
    if "annual_inc" in numeric_names:
        inc = X_num[:, numeric_names.index("annual_inc")]
        nn_blocks.append(
            ((jnp.isnan(inc)) | (inc == 0)).astype(jnp.float32)[:, None]
        )
        nn_names.append("no_income")
    if dti_idx >= 0:
        dti = X_num[:, dti_idx]
        nn_blocks.append(jnp.isnan(dti).astype(jnp.float32)[:, None])
        nn_names.append("dti_NA")
    for c in cat_present:
        cats = vocab[c]
        lookup = {v: i for i, v in enumerate(cats)}
        codes = df[c].map(lookup).fillna(len(cats)).to_numpy(np.float32)
        nn_blocks.append(jnp.asarray(codes)[:, None])
        nn_names.append(c)
    X_nn = jnp.concatenate(nn_blocks, axis=1)

    # One batched device->host fetch; per-scalar float(medians[i]) would block
    # ~0.1s per column on this backend (67 columns = ~7s of pure sync).
    medians_np = np.asarray(medians)
    median_map = {
        name: float(medians_np[i]) for i, name in enumerate(numeric_names)
    }
    plan = FeaturePlan(
        numeric_names=numeric_names,
        categorical_vocab=vocab,
        label_vocab=label_vocab,
        medians=median_map,
        log_cols=tuple(c for c in log_cols if c in numeric_names),
        tree_feature_names=tuple(tree_names),
        nn_feature_names=tuple(nn_names),
    )
    return (
        FeatureFrame(tuple(tree_names), X_tree, y),
        FeatureFrame(tuple(nn_names), X_nn, y),
        plan,
    )


def drop_training_leakage(ff: FeatureFrame) -> FeatureFrame:
    """Remove the trainer's leakage list (model_tree_train_test.py:82-87)."""
    return ff.drop([c for c in schema.TRAIN_LEAKAGE_COLS if c in ff.feature_names])
