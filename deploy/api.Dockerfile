# L4 serving container (reference: src/api/Dockerfile).
#
# The reference ships python:3.12-slim + pip requirements + uvicorn. Here the
# image installs this package with the [serve] extra (fastapi+uvicorn) and a
# TPU-enabled jax; on a TPU VM the container must run with the libtpu device
# exposed (--privileged or the TPU device plugin under GKE). Off-TPU the same
# image serves on CPU — jax falls back automatically, the scorer is the same
# compiled program.
#
# Build from the repo root:  docker build -f deploy/api.Dockerfile -t cobalt-lender-api .
FROM python:3.12-slim

ENV PYTHONDONTWRITEBYTECODE=1 \
    PYTHONUNBUFFERED=1

WORKDIR /app

COPY pyproject.toml README.md /app/
COPY cobalt_smart_lender_ai_tpu /app/cobalt_smart_lender_ai_tpu

# jax[tpu] pulls libtpu from the Google releases index; harmless on non-TPU
# hosts (falls back to CPU at runtime).
RUN pip install --upgrade pip && \
    pip install --no-cache-dir ".[serve,s3]" && \
    pip install --no-cache-dir "jax[tpu]" \
      -f https://storage.googleapis.com/jax-releases/libtpu_releases.html || true

# Model artifacts are restored at startup from COBALT_STORE_URI (file:// or
# s3://) — mount a volume or AWS credentials accordingly, mirroring the
# reference's ~/.aws mount in docker-compose.
EXPOSE 8000

CMD ["python", "-m", "cobalt_smart_lender_ai_tpu.serve", "--host", "0.0.0.0", "--port", "8000"]
