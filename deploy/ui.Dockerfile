# L5 UI container (reference: src/streamlit_ui/Dockerfile).
#
# Streamlit shell over the serving API; no JAX needed here — the UI only
# speaks HTTP to the api service.
#
# Build from the repo root:  docker build -f deploy/ui.Dockerfile -t cobalt-lender-ui .
FROM python:3.12-slim

ENV PYTHONDONTWRITEBYTECODE=1 \
    PYTHONUNBUFFERED=1

WORKDIR /app

COPY pyproject.toml README.md /app/
COPY cobalt_smart_lender_ai_tpu /app/cobalt_smart_lender_ai_tpu

RUN pip install --upgrade pip && \
    pip install --no-cache-dir ".[ui]" matplotlib

EXPOSE 8001

CMD ["streamlit", "run", "cobalt_smart_lender_ai_tpu/ui/app.py", \
     "--server.port=8001", "--server.address=0.0.0.0"]
