"""Generate and execute the exploration notebooks (reference capability C8).

The reference ships three executed exploration notebooks
(`notebooks/01_data_cleaning.ipynb`, `03_feature_engineering.ipynb`,
`04_model_training.ipynb`; `02_eda.ipynb` exists but its blob is missing from
the repo). Here the same exploration path is expressed against this
framework's APIs and *executed on commit* — run::

    python notebooks/make_notebooks.py

to rebuild. Execution runs on whatever backend the kernel sees — the
committed outputs were executed on a live TPU chip; on accelerator-free
hosts the env defaults below fall back to a virtual 8-device CPU mesh. The
data is a small synthetic table, so no LendingClub download is required.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import nbformat
from nbclient import NotebookClient

HERE = Path(__file__).resolve().parent
SETUP = """\
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
import sys, pathlib
_root = pathlib.Path.cwd()
while _root != _root.parent and not (_root / "cobalt_smart_lender_ai_tpu").is_dir():
    _root = _root.parent
if (_root / "cobalt_smart_lender_ai_tpu").is_dir():
    sys.path.insert(0, str(_root))  # repo checkout; else rely on installed pkg
import warnings; warnings.filterwarnings("ignore")
import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt
plt.rcParams["figure.dpi"] = 72
import numpy as np
import pandas as pd
import jax
print(f"jax devices: {len(jax.devices())} ({jax.devices()[0].platform})")
"""


def nb(cells) -> nbformat.NotebookNode:
    node = nbformat.v4.new_notebook()
    node.metadata["kernelspec"] = {
        "name": "python3",
        "display_name": "Python 3",
        "language": "python",
    }
    for kind, src in cells:
        if kind == "md":
            node.cells.append(nbformat.v4.new_markdown_cell(src))
        else:
            node.cells.append(nbformat.v4.new_code_cell(src))
    return node


CLEANING = [
    ("md", "# 01 — Data cleaning\n\n"
     "Interactive walk through the L1 cleaning stage (reference: "
     "`notebooks/01_data_cleaning.ipynb`, productionized in "
     "`src/data_preprocessing/clean_data.py:87-158`). The raw table here is "
     "the full-schema synthetic LendingClub generator — same columns, same "
     "string formats, same planted dirtiness (junk columns, null-heavy "
     "columns, duplicates)."),
    ("code", SETUP),
    ("code",
     "from cobalt_smart_lender_ai_tpu.data.synthetic import synthetic_lendingclub_frame\n"
     "raw = synthetic_lendingclub_frame(n_rows=20_000, seed=11)\n"
     "raw.shape"),
    ("md", "## Inspect the raw table\n\nNull fractions and dtypes first — the "
     "cleaning rules below are driven by exactly these observations."),
    ("code",
     "nulls = raw.isna().mean().sort_values(ascending=False)\n"
     "nulls.head(12).to_frame('null_fraction')"),
    ("code",
     "raw[['term', 'int_rate', 'emp_length', 'loan_status']].head()"),
    ("md", "## Apply the cleaning flow\n\nOne call applies all eight observable "
     "rules of the reference's `clean_data_flow`: drop `Unnamed:*` index "
     "artifacts, drop rows null in near-complete columns, fill "
     "`hardship_status`, parse `term`/`int_rate` strings to numbers, drop "
     ">70%-null columns, drop unnecessary columns, fill assumed-zero "
     "columns, drop duplicates."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame\n"
     "cleaned, report = clean_raw_frame(raw)\n"
     "report"),
    ("code",
     "print(f'rows {report.n_rows_in} -> {report.n_rows_out} '\n"
     "      f'({report.n_duplicates_removed} duplicates removed)')\n"
     "print(f'null-heavy columns dropped: {sorted(report.dropped_null_columns)}')\n"
     "cleaned[['term', 'int_rate']].describe().T"),
    ("md", "## Outlier glance\n\nThe reference notebook ends with a z-score "
     "outlier scan (cells 39-41) — flagged for awareness, not removed (tree "
     "models are robust to monotone outliers and the skewed columns get "
     "log1p in stage L2)."),
    ("code",
     "num = cleaned.select_dtypes('number')\n"
     "z = (num - num.mean()) / num.std()\n"
     "outlier_share = (z.abs() > 3).mean().sort_values(ascending=False)\n"
     "outlier_share.head(10).to_frame('share_|z|>3')"),
    ("code",
     "fig, ax = plt.subplots(figsize=(6, 3))\n"
     "ax.hist(cleaned['annual_inc'].dropna(), bins=60)\n"
     "ax.set_title('annual_inc — heavy right tail (log1p candidate)')\n"
     "plt.tight_layout(); plt.show()"),
]

EDA = [
    ("md", "# 02 — EDA\n\n"
     "Exploratory analysis of the cleaned table. (The reference's "
     "`02_eda.ipynb` blob is missing from its repo — this notebook fills the "
     "gap with the questions its pipeline implies: class balance, rate/grade "
     "structure, feature correlations.)"),
    ("code", SETUP),
    ("code",
     "from cobalt_smart_lender_ai_tpu.data.synthetic import synthetic_lendingclub_frame\n"
     "from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame\n"
     "cleaned, _ = clean_raw_frame(synthetic_lendingclub_frame(n_rows=20_000, seed=11))\n"
     "cleaned.shape"),
    ("md", "## Label balance\n\n`loan_status` maps to the binary "
     "`loan_default` label downstream; defaults are the minority class, which "
     "is why training uses `scale_pos_weight`."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.data.schema import LOAN_STATUS_MAP\n"
     "status = cleaned['loan_status'].value_counts()\n"
     "default_rate = cleaned['loan_status'].map(LOAN_STATUS_MAP).mean()\n"
     "print(f'default rate: {default_rate:.3f}')\n"
     "status.to_frame('count')"),
    ("code",
     "fig, ax = plt.subplots(figsize=(6, 3))\n"
     "by_grade = (cleaned.assign(d=cleaned['loan_status'].map(LOAN_STATUS_MAP))\n"
     "            .groupby('grade')['d'].mean())\n"
     "ax.bar(by_grade.index, by_grade.values)\n"
     "ax.set_ylabel('default rate'); ax.set_title('Default rate by grade')\n"
     "plt.tight_layout(); plt.show()"),
    ("md", "## Rate structure\n\nInterest rate should rise with grade — the "
     "underwriting signal the model learns from."),
    ("code",
     "fig, ax = plt.subplots(figsize=(6, 3))\n"
     "cleaned.boxplot(column='int_rate', by='grade', ax=ax)\n"
     "ax.set_title('int_rate by grade'); plt.suptitle('')\n"
     "plt.tight_layout(); plt.show()"),
    ("md", "## Correlations\n\nTop absolute correlations with the label among "
     "numeric columns — note the suspiciously strong payment/recovery "
     "columns: those are *post-outcome* leakage and are dropped before "
     "training (see notebook 04)."),
    ("code",
     "num = cleaned.select_dtypes('number').copy()\n"
     "num['loan_default'] = cleaned['loan_status'].map(LOAN_STATUS_MAP)\n"
     "corr = num.corr(numeric_only=True)['loan_default'].drop('loan_default')\n"
     "corr.abs().sort_values(ascending=False).head(12).to_frame('|corr|')"),
]

FEATURES = [
    ("md", "# 03 — Feature engineering\n\n"
     "The L2 stage (reference: `notebooks/03_feature_engineering.ipynb`, "
     "productionized in `src/data_preprocessing/feature_engineering.py`). "
     "String-heavy prep stays on host; every O(N) numeric transform (log1p, "
     "one-hot, impute+indicator) runs jitted on device over the whole "
     "matrix at once — the reference's slowest construct was a row-wise "
     "Python `.apply` log1p loop."),
    ("code", SETUP),
    ("code",
     "from cobalt_smart_lender_ai_tpu.data.synthetic import synthetic_lendingclub_frame\n"
     "from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame\n"
     "from cobalt_smart_lender_ai_tpu.data.features import prepare_cleaned_frame, engineer_features\n"
     "cleaned, _ = clean_raw_frame(synthetic_lendingclub_frame(n_rows=20_000, seed=11))\n"
     "prepared = prepare_cleaned_frame(cleaned)\n"
     "prepared.shape"),
    ("md", "## Host-side preparation\n\n`prepare_cleaned_frame` performs the "
     "irreducibly stringy work: leakage/useless column drops, `emp_length` "
     "to numeric, `revol_util` percent to ratio, `earliest_cr_line` to "
     "day counts, and the `loan_status` → `loan_default` label map."),
    ("code",
     "prepared[['emp_length_num', 'revol_util', 'earliest_cr_line_days', 'loan_default']].head()"),
    ("md", "## Device-side engineering\n\nOne call produces both model frames: "
     "the tree frame (one-hot categoricals, NaNs kept for learned missing "
     "routing) and the NN frame (median impute + missing indicators). The "
     "`FeaturePlan` records every learned statistic so serving can replay "
     "the exact transform."),
    ("code",
     "tree_ff, nn_ff, plan = engineer_features(prepared)\n"
     "print(f'tree frame: {tree_ff.n_rows} x {tree_ff.n_features}')\n"
     "print(f'nn frame:   {nn_ff.n_rows} x {nn_ff.n_features}')\n"
     "print(f'plan: {len(plan.numeric_names)} numeric, '\n"
     "      f'{len(plan.categorical_vocab)} categorical vocabularies, '\n"
     "      f'{len(plan.medians)} medians recorded')"),
    ("code",
     "import numpy as np\n"
     "from cobalt_smart_lender_ai_tpu.data.schema import LOG_COLS\n"
     "col = 'annual_inc'\n"
     "before = prepared[col].to_numpy(dtype=float)\n"
     "after = np.asarray(tree_ff.column(col))\n"
     "fig, axes = plt.subplots(1, 2, figsize=(8, 2.5))\n"
     "axes[0].hist(before[~np.isnan(before)], bins=50); axes[0].set_title(f'{col} raw')\n"
     "axes[1].hist(after[~np.isnan(after)], bins=50); axes[1].set_title(f'{col} log1p (device)')\n"
     "plt.tight_layout(); plt.show()"),
    ("md", "## One-hot expansion\n\n`get_dummies(drop_first=True)` semantics: "
     "each categorical's first vocabulary value is the implicit baseline."),
    ("code",
     "onehot_cols = [n for n in tree_ff.feature_names if any(\n"
     "    n.startswith(p + '_') for p in ('grade', 'home_ownership', 'verification_status',\n"
     "                                    'purpose', 'application_type', 'hardship_status'))]\n"
     "print(f'{len(onehot_cols)} one-hot columns, e.g. {onehot_cols[:6]}')"),
    ("md", "## NN frame: impute + indicators\n\nThe NN path cannot route "
     "missing values through split logic, so medians fill the gaps and "
     "`*_missing` indicator columns preserve the missingness signal."),
    ("code",
     "missing_ind = [n for n in nn_ff.feature_names if n.endswith('_missing')]\n"
     "print(f'{len(missing_ind)} missing-indicator columns, e.g. {missing_ind[:5]}')\n"
     "assert not np.isnan(np.asarray(nn_ff.X)).any(), 'NN frame must be NaN-free'\n"
     "print('NN frame is NaN-free')"),
]

TRAINING = [
    ("md", "# 04 — Model training\n\n"
     "The L3 exploration path (reference: `notebooks/04_model_training.ipynb`): "
     "leakage demonstration, split + class weighting, RFE feature selection, "
     "randomized hyperparameter search fanned out over the device mesh, final "
     "evaluation, TreeSHAP explanation, and the MLP challenger. Small "
     "synthetic table + light settings so this executes in minutes on the "
     "8-device virtual CPU mesh; the production path is "
     "`cobalt_smart_lender_ai_tpu/pipeline.py`."),
    ("code", SETUP),
    ("code",
     "from cobalt_smart_lender_ai_tpu.data.synthetic import synthetic_lendingclub_frame\n"
     "from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame\n"
     "from cobalt_smart_lender_ai_tpu.data.features import (\n"
     "    prepare_cleaned_frame, engineer_features, drop_training_leakage)\n"
     "cleaned, _ = clean_raw_frame(synthetic_lendingclub_frame(n_rows=8_000, seed=5))\n"
     "tree_ff, nn_ff, plan = engineer_features(prepare_cleaned_frame(cleaned))\n"
     "tree_ff.n_features"),
    ("md", "## The leakage lesson\n\nThe reference's first model scored AUC "
     "0.9993 — 'suspiciously too good' (its notebook cell 12) — because "
     "payment-history columns encode the outcome. Reproduce, then drop them."),
    ("code",
     "import jax.numpy as jnp\n"
     "from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier\n"
     "from cobalt_smart_lender_ai_tpu.data.split import train_test_split_hashed\n"
     "from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc\n"
     "Xtr, Xte, ytr, yte = train_test_split_hashed(tree_ff.X, tree_ff.y, test_fraction=0.2, seed=22)\n"
     "leaky = GBDTClassifier(n_estimators=30, max_depth=3, n_bins=32).fit(np.asarray(Xtr), np.asarray(ytr))\n"
     "leaky_auc = float(roc_auc(jnp.asarray(np.asarray(yte), jnp.float32), leaky.predict_margin(np.asarray(Xte))))\n"
     "print(f'AUC with leakage columns: {leaky_auc:.4f}  <- suspiciously good')"),
    ("code",
     "ff = drop_training_leakage(tree_ff)\n"
     "Xtr, Xte, ytr, yte = train_test_split_hashed(ff.X, ff.y, test_fraction=0.2, seed=22)\n"
     "Xtr, Xte, ytr, yte = map(np.asarray, (Xtr, Xte, ytr, yte))\n"
     "honest = GBDTClassifier(n_estimators=30, max_depth=3, n_bins=32).fit(Xtr, ytr)\n"
     "honest_auc = float(roc_auc(jnp.asarray(yte, jnp.float32), honest.predict_margin(Xte)))\n"
     "print(f'AUC after leakage drop:   {honest_auc:.4f}')\n"
     "assert honest_auc < leaky_auc"),
    ("md", "## Class weighting\n\nDefaults are the minority class; "
     "`scale_pos_weight = n_neg / n_pos` reweights the positive gradient "
     "(the reference computes exactly this, `model_tree_train_test.py:103-106`)."),
    ("code",
     "spw = float((len(ytr) - ytr.sum()) / max(ytr.sum(), 1))\n"
     "print(f'scale_pos_weight = {spw:.3f}')"),
    ("md", "## RFE to 20 features\n\nMasked refits with static shapes — "
     "dropped features are masked, not removed, so every refit reuses one "
     "compiled program (the reference's RFE ran ~123 sequential XGBoost "
     "fits). `step=10` here for notebook speed; production uses step=1."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.config import RFEConfig, MeshConfig, TuneConfig, GBDTConfig\n"
     "from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh\n"
     "from cobalt_smart_lender_ai_tpu.parallel.rfe import rfe_select\n"
     "mesh = make_mesh(MeshConfig())\n"
     "rfe = rfe_select(Xtr, ytr, RFEConfig(n_select=20, step=10, n_estimators=30,\n"
     "                                     max_depth=3, scale_pos_weight=spw), mesh=mesh)\n"
     "selected = [n for n, keep in zip(ff.feature_names, rfe.support_) if keep]\n"
     "print(f'{len(selected)} selected: {selected}')"),
    ("md", "## Randomized search on the mesh\n\nThe reference's "
     "`RandomizedSearchCV(n_iter=20, cv=3)` forked 60 joblib processes; here "
     "fold x candidate jobs fan out across devices in one dispatch."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.parallel.tune import randomized_search\n"
     "sel = np.flatnonzero(rfe.support_)\n"
     "Xtr_sel, Xte_sel = Xtr[:, sel], Xte[:, sel]\n"
     "base = GBDTConfig(n_bins=32).replace(scale_pos_weight=spw)\n"
     "search = randomized_search(Xtr_sel, ytr, base,\n"
     "                           TuneConfig(n_iter=8, cv_folds=3, seed=22), mesh)\n"
     "print(f'best CV AUC {search.best_score_:.4f}')\n"
     "search.best_params_"),
    ("md", "## Final evaluation"),
    ("code",
     "from cobalt_smart_lender_ai_tpu.ops.metrics import binary_classification_report\n"
     "est = search.best_estimator_\n"
     "test_auc = float(roc_auc(jnp.asarray(yte, jnp.float32), est.predict_margin(Xte_sel)))\n"
     "report = binary_classification_report(jnp.asarray(yte, jnp.float32),\n"
     "                                      jnp.asarray(np.asarray(est.predict(Xte_sel))))\n"
     "print(f'test ROC-AUC: {test_auc:.4f}')\n"
     "pd.DataFrame(report).T"),
    ("md", "## TreeSHAP explanation\n\nExact path-dependent TreeSHAP over the "
     "tree tensors (the reference uses shap's C++ TreeExplainer, its "
     "notebook cells 25-26). Additivity: base + sum(phi) equals the margin."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.explain.treeshap import shap_values\n"
     "phis, base = shap_values(est.forest, jnp.asarray(Xte_sel[:1]), n_features=len(sel))\n"
     "margin = float(est.predict_margin(Xte_sel[:1])[0])\n"
     "print(f'base {float(base):+.4f} + sum(phi) {float(phis.sum()):+.4f} = {float(base)+float(phis.sum()):+.4f}'\n"
     "      f'  (margin {margin:+.4f})')\n"
     "order = np.argsort(-np.abs(np.asarray(phis)[0]))[:8]\n"
     "fig, ax = plt.subplots(figsize=(6, 3))\n"
     "ax.barh([selected[i] for i in order][::-1], np.asarray(phis)[0][order][::-1])\n"
     "ax.set_title('Top SHAP contributions, row 0'); plt.tight_layout(); plt.show()"),
    ("md", "## Multi-row SHAP explorer\n\nThe reference explores per-row "
     "explanations with an ipywidgets slider over force plots (its cells "
     "25-26). Same capability: SHAP for a whole batch in one device call, "
     "an `explain_row(i)` renderer, wired to `ipywidgets.interact` when "
     "available (offline executions render a sample of rows statically)."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.ui.core import build_waterfall, render_waterfall\n"
     "n_explore = 20\n"
     "phis_b, base_b = shap_values(est.forest, jnp.asarray(Xte_sel[:n_explore]), n_features=len(sel))\n"
     "phis_b = np.asarray(phis_b)\n"
     "def explain_row(i=0):\n"
     "    resp = {'shap_values': phis_b[i].tolist(), 'base_value': float(base_b),\n"
     "            'features': selected,\n"
     "            'input_row': {n: float(v) for n, v in zip(selected, Xte_sel[i])}}\n"
     "    fig, ax = plt.subplots(figsize=(8, 4))\n"
     "    render_waterfall(ax, build_waterfall(resp, max_display=10))\n"
     "    ax.set_title(f'row {i}: margin {float(base_b) + phis_b[i].sum():+.3f}')\n"
     "    plt.tight_layout(); plt.show()\n"
     "try:\n"
     "    from ipywidgets import interact\n"
     "    interact(explain_row, i=(0, n_explore - 1))\n"
     "except ImportError:  # offline execution: render a sample statically\n"
     "    for i in (0, 7, 13):\n"
     "        explain_row(i)"),
    ("md", "## MLP challenger\n\nFlax MLP (128/32/16) + optax AdamW with "
     "exponential LR decay and early stopping — the reference's Keras "
     "challenger, with its dead `val_precision` monitor fixed and "
     "class-weighted BCE replacing SMOTE."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.config import MLPConfig\n"
     "from cobalt_smart_lender_ai_tpu.models.nn import MLPClassifier\n"
     "Xtr_nn, Xte_nn, ytr_nn, yte_nn = map(np.asarray, train_test_split_hashed(\n"
     "    nn_ff.X, nn_ff.y, test_fraction=0.2, seed=22))\n"
     "mlp = MLPClassifier(MLPConfig(epochs=15)).fit(Xtr_nn, ytr_nn, Xte_nn, yte_nn)\n"
     "mlp_auc = float(roc_auc(jnp.asarray(yte_nn, jnp.float32), mlp.predict_logits(Xte_nn)))\n"
     "print(f'MLP test ROC-AUC: {mlp_auc:.4f}  (GBDT: {test_auc:.4f})')"),
    ("md", "## Gain importances\n\nThe static booster gains behind the "
     "`/feature_importance_bulk` endpoint."),
    ("code",
     "from cobalt_smart_lender_ai_tpu.models.gbdt import gain_importances\n"
     "total_gain, _ = gain_importances(est.forest, len(sel))\n"
     "order = np.argsort(-np.asarray(total_gain))[:10]\n"
     "fig, ax = plt.subplots(figsize=(6, 3))\n"
     "ax.barh([selected[i] for i in order][::-1], np.asarray(total_gain)[order][::-1])\n"
     "ax.set_title('Top-10 gain importances'); plt.tight_layout(); plt.show()"),
]


def build(name: str, cells, execute: bool = True) -> None:
    node = nb(cells)
    if execute:
        print(f"executing {name} ...", flush=True)
        NotebookClient(node, timeout=1200, kernel_name="python3").execute()
    path = HERE / name
    nbformat.write(node, path)
    print(f"wrote {path}")


if __name__ == "__main__":
    execute = "--no-execute" not in sys.argv
    build("01_data_cleaning.ipynb", CLEANING, execute)
    build("02_eda.ipynb", EDA, execute)
    build("03_feature_engineering.ipynb", FEATURES, execute)
    build("04_model_training.ipynb", TRAINING, execute)
